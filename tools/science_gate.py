#!/usr/bin/env python
"""Deterministic behavioral-drift gate: the science, machine-checked.

tools/perf_gate.py pins the COMPILED PROGRAMS (static HLO cost facts);
nothing pins the BEHAVIOR — the defense x attack accuracy/ASR surface
that is the paper's entire contribution.  Through PR 4 that baseline
lived in hand-maintained tables (PARITY.md, GRID_RESULTS.md) and in the
behavioral tests' generous directional margins; a constant drifting by
a few points (an attack z, a trim fraction, a selection quirk) could
slide through every margin and silently rewrite the science.

This gate replays a pinned set of SYNTH_MNIST_HARD defense x attack
cells — seeded, CPU, short-round, the same low-SNR dataset the
behavioral tests pin (tests/test_behavior.py; CLAUDE.md "behavioral
tuning facts") — and diffs final/max accuracy, backdoor ASR and Krum
selection concentration against the checked-in BEHAVIOR_BASELINE.json.

Tolerance policy (ARCHITECTURE.md "Run registry & science gate"):

- metrics with ``band == 0`` must match EXACTLY — an identical program
  on an identical (env, seed) replays bit-for-bit, so any drift is a
  real behavioral change;
- selection-mediated metrics carry a small MEASURED band: PR 4's
  ulp-tie adjudication (tests/test_distance_impl.py, bench.py
  adjudicate_f32_flip) showed Krum/Bulyan selections rest on f32
  near-ties where a legal compile-schedule change (reduction reorder,
  re-fusion) flips a pick at 1 ulp and the flip cascades into the
  trajectory.  Exact-match there would veto legal optimizations; the
  bands bound how far a legal flip was ever observed to move each
  metric.

The baseline records its environment (jax/jaxlib/platform); on a
mismatch the comparison is meaningless and the gate SKIPS with a loud
notice and exit 0 unless ``--strict-env`` (perf_gate's policy) —
regenerate with ``--update`` after a toolchain bump.

Usage:
    python tools/science_gate.py                   # gate
    python tools/science_gate.py --update          # (re)generate
    python tools/science_gate.py --cells krum_alie05,nodefense_clean
    python tools/science_gate.py --events logs/gate.jsonl   # v4 'gate'
                                                            # events

Exit status: 0 clean (or env-skip), 1 on any named cell.metric drift,
2 when the baseline is missing.  CI-wired via tools/smoke.sh leg 5 and
tests/test_science_gate.py (which exercises the diff on perturbed
measurements — the "a constant changed" failure mode — without paying
for cell replays).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BEHAVIOR_BASELINE.json")

# The pinned grid slice: the behavioral-test constants (n=19, ~21%
# malicious, batch 64 — ALIE strength depends on 1/sqrt(batch),
# CLAUDE.md) at gate-sized rounds.  Cells cover the mechanisms the
# paper's surface is made of: the clean baselines, the z-dependent ALIE
# split (z=0.5 defeats averaging AND Krum; z=1.5 degrades the
# coordinate-wise and Bulyan estimators), and backdoor ASR.
ROUNDS = 10
CELLS = {
    "nodefense_clean": dict(defense="NoDefense", attack=None),
    "nodefense_alie05": dict(defense="NoDefense", z=0.5),
    "krum_clean": dict(defense="Krum", attack=None, telemetry=True),
    "krum_alie05": dict(defense="Krum", z=0.5, telemetry=True),
    "krum_alie15": dict(defense="Krum", z=1.5, telemetry=True),
    "trimmedmean_alie15": dict(defense="TrimmedMean", z=1.5),
    "bulyan_alie15": dict(defense="Bulyan", z=1.5),
    "backdoor_trimmedmean": dict(defense="TrimmedMean", backdoor=True),
    # --- PR 7: the secure-aggregation scenario (protocols/secagg.py).
    # vanilla must replay the clear NoDefense cell bit-for-bit (the
    # protocol is behaviorally invisible — masking cancels exactly),
    # so its values double as a cross-cell invariant with
    # nodefense_alie05.  groupwise composes with the two-tier tree:
    # n=20/m=5 so the megabatch divides, tier-2 Krum over group sums
    # (selection-mediated -> banded like the krum cells).
    "secagg_vanilla_alie05": dict(defense="NoDefense", z=0.5,
                                  secagg="vanilla"),
    "secagg_groupwise_alie15": dict(defense="NoDefense", z=1.5, n=20,
                                    mal_prop=0.2, secagg="groupwise",
                                    aggregation="hierarchical",
                                    megabatch=5, tier2_defense="Krum"),
    # --- PR 8: hierarchical forensics (ISSUE 8 acceptance).  The
    # concentrated-placement Krum row from the round-6 science, now
    # pinned through the TELEMETRY path: n=20/m=5 packs all f=4
    # colluders into shard 0, tier-2 Krum must reject that shard's
    # estimate every round, and the forensics layer
    # (report.py:forensics_summary over the same shard_selection
    # stream a logged run emits) must return the 'localized' verdict
    # naming shard 0 — tier-2 rejection counts pinned, banded like
    # every selection-mediated cell.
    "hier_krum_conc_forensics": dict(defense="Krum", z=1.5, n=20,
                                     mal_prop=0.2,
                                     aggregation="hierarchical",
                                     megabatch=5,
                                     mal_placement="concentrated",
                                     telemetry=True),
    # --- PR 9: asynchronous buffered rounds (ISSUE 9, core/
    # async_rounds.py).  The behavioral-test constants under the
    # FedBuff regime: k=12 of n=19 aggregated per applied round,
    # staleness bound 2, poly weighting.  The clean NoDefense cell is
    # a pure deterministic replay (no selection anywhere — the FIFO
    # order is PRNG-fixed), band 0; the Krum×ALIE cell is
    # selection-mediated, banded like the sync krum cells.
    "async_nodefense_clean": dict(defense="NoDefense", attack=None,
                                  aggregation="async", async_buffer=12,
                                  async_max_staleness=2,
                                  staleness_weight="poly"),
    "async_krum_alie15": dict(defense="Krum", z=1.5,
                              aggregation="async", async_buffer=12,
                              async_max_staleness=2,
                              staleness_weight="poly"),
    # --- PR 17: population traffic (ISSUE 17, core/population.py).
    # The behavioral constants under sampled-cohort churn: each
    # round's 19 rows are drawn from a deliberately tight 24-client
    # registry at rate 0.5 (dwell-3 churn episodes), so the cohort
    # under-fills Krum's 2f+3 validity bound on some rounds and walks
    # the whole degradation ladder (7 remask / 2 TrimmedMean fallback
    # / 1 hold at these constants).  The schedule facts (arrived_mean,
    # degraded_rounds) replay exactly — the schedule is pure in
    # (TrafficConfig, seed, t) — band 0; the accuracy is
    # Krum-selection-mediated over a changing cohort, banded like the
    # other krum cells.
    "traffic_krum_churn": dict(defense="Krum", z=1.5,
                               traffic=dict(population=24, rate=0.5,
                                            churn_dwell=3,
                                            fallback_defense="TrimmedMean",
                                            seed=17)),
    # --- PR 18: robustness margins (ISSUE 18, utils/margins.py).  The
    # GRID round-5 Bulyan z=1.5 pair (19 clients, 20% malicious,
    # style_strength 0.5, 30 rounds — margins need the full study
    # length; the tie structure only breaks after convergence starts),
    # now pinned through the MARGIN observatory.  The MEASURED
    # mechanism, sharper than the working hypothesis of a simple sign
    # flip: under IID the identical crafted rows are score-degenerate,
    # so a selected colluder's runner-up is its own twin and the
    # colluder margin is EXACTLY zero (equal f32 scores subtract to
    # zero under any legal schedule) — the selection is tie-locked at
    # the decision boundary ~28/30 rounds, colluders are almost never
    # selected by a strictly positive margin (2 round-events), and
    # training collapses to ~10%.  Under femnist_style the honest
    # rows' per-client structure widens the cohort sigma, the crafted
    # cluster stops straddling the cut, and the tie-lock BREAKS from
    # ~round 19: strictly-signed margins appear and PERSIST (19/30 tie
    # rounds, 11 strict-selection events) while training converges —
    # the round-5 rescue, restated as the margin leaving the decision
    # boundary.  All margin metrics are selection-mediated (banded);
    # the collapse/rescue bands do not overlap.
    "bulyan_margin_collapse": dict(defense="Bulyan", z=1.5,
                                   mal_prop=0.2, margins=True,
                                   rounds=30),
    "bulyan_margin_rescue": dict(defense="Bulyan", z=1.5, mal_prop=0.2,
                                 margins=True, rounds=30,
                                 partition="femnist_style",
                                 style_strength=0.5),
    # --- PR 19: shard-domain faults in the hierarchical tree (ISSUE
    # 19, core/faults.py).  The behavioral constants under correlated
    # shard death: n=20/m=4 gives S=5 shards — exactly tier-2 Krum's
    # 2f+3 validity floor at f2=1, so every dead domain under-fills the
    # bound and the round walks the remask/fallback/hold ladder.  The
    # schedule facts (dead-domain rounds, shard-round deaths,
    # quarantine total, per-rung ladder counts) replay exactly — the
    # schedule is pure in (fault key, t) — band 0; the accuracy is
    # Krum-selection-mediated over a changing shard cohort, banded
    # like the other krum cells.
    "hier_krum_shard_dropout": dict(defense="Krum", z=1.5, n=20,
                                    mal_prop=0.2,
                                    aggregation="hierarchical",
                                    megabatch=4,
                                    faults=dict(dropout=0.1,
                                                shard_dropout=0.2,
                                                shard_dropout_dwell=2)),
}

# Per-metric tolerance bands (absolute; 0 = exact).  Authored here,
# recorded into the baseline at --update so the gate run states the
# policy it was compared under.  Rationale: mean/coordinate-wise paths
# with no data-dependent selection replay exactly; selection-mediated
# cells (Krum picks, Bulyan's select+trim, the backdoor's clip-envelope
# race) may legally move under a 1-ulp compile-schedule flip
# (tests/test_distance_impl.py::test_engine_bulyan_blockwise — the
# measured mechanism), so they carry bands sized generously below any
# real behavioral effect (the PARITY table's effects are tens of
# points).
DEFAULT_BANDS = {"final_accuracy": 0.0, "max_accuracy": 0.0}
CELL_BANDS = {
    "krum_clean": {"final_accuracy": 2.0, "max_accuracy": 2.0,
                   "top1_share": 0.1, "malicious_share": 0.05,
                   "distinct_winners": 2},
    "krum_alie05": {"final_accuracy": 3.0, "max_accuracy": 3.0,
                    "top1_share": 0.1, "malicious_share": 0.1,
                    "distinct_winners": 2},
    "krum_alie15": {"final_accuracy": 2.0, "max_accuracy": 2.0,
                    "top1_share": 0.1, "malicious_share": 0.05,
                    "distinct_winners": 2},
    "bulyan_alie15": {"final_accuracy": 5.0, "max_accuracy": 5.0},
    "trimmedmean_alie15": {"final_accuracy": 2.0, "max_accuracy": 2.0},
    "backdoor_trimmedmean": {"final_accuracy": 2.0, "max_accuracy": 2.0,
                             "final_asr": 5.0},
    # vanilla secagg is the NoDefense mean over a bit-identically
    # recovered matrix: no selection anywhere, so exact (band 0 via
    # DEFAULT_BANDS).  groupwise runs tier-2 Krum over group sums:
    # selection-mediated, same band family as the krum cells.
    "secagg_groupwise_alie15": {"final_accuracy": 2.0,
                                "max_accuracy": 2.0},
    # Forensics attribution: the localization VERDICT is pinned exact
    # (the colluder shard's estimate is the crafted vector itself —
    # no ulp tie to flip), the round counts and the tier-2 selection
    # mass carry small bands for the usual selection-mediated wiggle.
    "hier_krum_conc_forensics": {"final_accuracy": 5.0,
                                 "max_accuracy": 5.0,
                                 "localized": 0.0,
                                 "stabilized_round": 2.0,
                                 "mal_rejected_rounds": 2.0,
                                 "tier2_malicious_share": 0.05},
    # async_nodefense_clean is exact (band 0 via DEFAULT_BANDS): the
    # weighted mean + deterministic FIFO replay bit-for-bit.  The
    # async Krum cell is selection-mediated (delivered-cohort Krum
    # picks rest on the same f32 near-ties as the sync cells).
    "async_krum_alie15": {"final_accuracy": 3.0, "max_accuracy": 3.0},
    # Churned-cohort Krum: accuracy is selection-mediated (same ulp-tie
    # mechanism, now over per-round sampled rows); the schedule facts
    # are exact host replays (band 0 via the metric defaults).
    "traffic_krum_churn": {"final_accuracy": 3.0, "max_accuracy": 3.0},
    # Faulted-hierarchy Krum: accuracy is selection-mediated at BOTH
    # tiers (per-shard Krum over a quarantined cohort, tier-2 over the
    # survivors); the shard-domain schedule facts are exact host
    # replays (band 0 via the metric defaults).
    "hier_krum_shard_dropout": {"final_accuracy": 3.0,
                                "max_accuracy": 3.0},
    # Margin cells: every metric reads the f32 distance scores the
    # selections rest on, so all carry selection-mediated bands; the
    # DISCRIMINATORS (margin_tie_rounds 28 vs 19, band 3/4;
    # colluder_selected_total 2 vs 11, band 3/4) keep non-overlapping
    # bands, so a legal ulp flip cannot turn one cell into the other.
    "bulyan_margin_collapse": {"final_accuracy": 5.0,
                               "max_accuracy": 5.0,
                               "margin_tie_rounds": 3,
                               "colluder_margin_min": 1.2,
                               "colluder_margin_final": 0.05,
                               "margin_breached_rounds": 2,
                               "colluder_selected_total": 3},
    "bulyan_margin_rescue": {"final_accuracy": 5.0,
                             "max_accuracy": 5.0,
                             "margin_tie_rounds": 4,
                             "colluder_margin_min": 0.5,
                             "colluder_margin_final": 0.3,
                             "margin_breached_rounds": 2,
                             "colluder_selected_total": 4},
}


def environment() -> dict:
    import importlib.metadata as md

    import jax

    def _v(pkg):
        try:
            return md.version(pkg)
        except Exception:
            return "unknown"

    return {"jax": _v("jax"), "jaxlib": _v("jaxlib"),
            "platform": jax.devices()[0].platform}


def measure_cell(name: str, spec: dict, rounds: int = ROUNDS) -> dict:
    """Replay one pinned cell; returns {metric: value}.  Seeded,
    short-round, CPU-sized — the behavioral-test recipe
    (tests/conftest.py:hard_final_accuracy) at gate cadence."""
    import numpy as np

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import (
        DriftAttack, NoAttack, make_attacker
    )
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset

    # A cell may pin its own length (the margin cells ride the GRID
    # round-5 30-round protocol — the tie structure they pin only
    # breaks after convergence starts); everything else runs at the
    # gate cadence.
    rounds = spec.get("rounds", rounds)
    backdoor = spec.get("backdoor", False)
    attacked = spec.get("attack", "alie") is not None or backdoor
    cfg = ExperimentConfig(
        dataset=C.SYNTH_MNIST_HARD, users_count=spec.get("n", 19),
        mal_prop=spec.get("mal_prop", 0.21 if attacked else 0.0),
        batch_size=64,
        epochs=rounds, test_step=max(1, rounds // 2), seed=0,
        synth_train=4000, synth_test=1000,
        defense=spec["defense"],
        num_std=spec.get("z", 1.5),
        backdoor="pattern" if backdoor else False,
        telemetry=bool(spec.get("telemetry")),
        secagg=spec.get("secagg", "off"),
        aggregation=spec.get("aggregation", "flat"),
        megabatch=spec.get("megabatch", 0),
        tier2_defense=spec.get("tier2_defense"),
        mal_placement=spec.get("mal_placement", "spread"),
        margins=bool(spec.get("margins")),
        partition=spec.get("partition", "iid"),
        style_strength=spec.get("style_strength", 0.25),
        async_buffer=spec.get("async_buffer", 0),
        async_max_staleness=spec.get("async_max_staleness", 2),
        staleness_weight=spec.get("staleness_weight", "none"),
        traffic=(C.TrafficConfig(**spec["traffic"])
                 if "traffic" in spec else None),
        faults=(C.FaultConfig(**spec["faults"])
                if "faults" in spec else None))
    ds = load_dataset(cfg.dataset, seed=0, synth_train=cfg.synth_train,
                      synth_test=cfg.synth_test)
    if backdoor:
        attacker = make_attacker(cfg, dataset=ds, name="backdoor")
    elif spec.get("attack", "alie") is None:
        attacker = NoAttack()
    else:
        attacker = DriftAttack(cfg.num_std)
    exp = FederatedExperiment(cfg, attacker=attacker, dataset=ds)

    accs, winners, shard_events, margin_rounds = [], [], [], []
    hier = cfg.aggregation == "hierarchical"
    eval_rounds = {t for t in range(rounds)
                   if t % cfg.test_step == 0 or t == rounds - 1}
    for t in range(rounds):
        exp.run_round(t)
        if cfg.margins and exp.last_round_telemetry is not None:
            # The colluder-survival rollup over the round's margin
            # fields — the same reduction the engine's v12 'margin'
            # event carries (utils/margins.py:margin_rollups).
            from attacking_federate_learning_tpu.utils.margins import (
                margin_rollups
            )
            mf = {k[len("defense_"):]: np.asarray(v)
                  for k, v in exp.last_round_telemetry.items()
                  if k.startswith("defense_margin_")}
            if mf:
                margin_rounds.append(margin_rollups(mf, exp.m_mal))
        if cfg.telemetry and exp.last_round_telemetry is not None:
            if hier:
                # Rebuild the round's 'shard_selection' payload the
                # engine would log (core/engine.py shares the static
                # fields), so the forensics verdict the gate pins is
                # computed by the SAME code path 'report forensics'
                # runs on a real event log.
                rec = {"kind": "shard_selection", "round": t,
                       **exp._shard_static_fields()}
                for k, v in exp.last_round_telemetry.items():
                    if k.startswith(("shard_", "tier2_")):
                        rec[k] = np.asarray(v).astype(float).tolist()
                shard_events.append(rec)
            else:
                mask = np.asarray(exp.last_round_telemetry.get(
                    "defense_selection_mask"))
                if mask.ndim == 1 and np.isfinite(mask).all():
                    winners.append(int(np.argmax(mask)))
        if t in eval_rounds:
            _, correct = exp.evaluate(exp.state.weights)
            accs.append(100.0 * float(correct) / len(ds.test_y))
    out = {"final_accuracy": round(accs[-1], 4),
           "max_accuracy": round(max(accs), 4)}
    if cfg.traffic is not None and cfg.traffic.enabled:
        # Schedule facts from the host replay (pure in config + t):
        # average arrived cohort and ladder-degraded round count.
        from attacking_federate_learning_tpu.core.population import (
            replay_traffic
        )

        tev = replay_traffic(cfg, rounds)
        out["arrived_mean"] = round(
            sum(e["arrived"] for e in tev) / len(tev), 4)
        out["degraded_rounds"] = sum(
            1 for e in tev if e["action"] != "remask")
    if cfg.faults is not None and hier:
        # Shard-domain schedule facts from the host replay (pure in
        # the fault key + t): dead-domain incidence, quarantine mass,
        # and the tier-2 ladder's per-rung round counts.
        from attacking_federate_learning_tpu.core.faults import (
            hier_fault_schedule, plan_tier2_actions
        )
        from attacking_federate_learning_tpu.core.population import (
            ACTION_NAMES
        )

        rows = hier_fault_schedule(exp._fault_key, 0, rounds,
                                   exp._placement, exp.faults)
        acts = plan_tier2_actions([r["shards_alive"] for r in rows],
                                  exp._tier2_name, exp._tier2_f)
        out["dead_domain_rounds"] = sum(
            1 for r in rows if r["shards_dead"] > 0)
        out["shard_deaths_total"] = sum(r["shards_dead"] for r in rows)
        out["quarantined_total"] = sum(r["quarantined"] for r in rows)
        for i, rung in enumerate(ACTION_NAMES):
            out[f"tier2_{rung}_rounds"] = int(np.sum(acts == i))
    if shard_events:
        from attacking_federate_learning_tpu.report import (
            forensics_summary
        )

        fx = forensics_summary(shard_events)
        loc, t2 = fx["localization"], fx.get("tier2", {})
        localized = loc.get("verdict") == "localized"
        out["localized"] = 1 if localized else 0
        out["stabilized_round"] = (loc.get("stabilized_round")
                                   if localized else -1)
        if "mal_rejected_rounds" in t2:
            out["mal_rejected_rounds"] = t2["mal_rejected_rounds"]
            out["tier2_malicious_share"] = t2["malicious_share"]
    if margin_rounds:
        cms = [r["colluder_margin"] for r in margin_rounds
               if r.get("colluder_margin") is not None]
        out["colluder_margin_min"] = round(float(min(cms)), 4)
        out["colluder_margin_final"] = round(float(cms[-1]), 4)
        out["margin_breached_rounds"] = sum(1 for v in cms if v <= 0)
        out["colluder_selected_total"] = int(sum(
            r.get("colluder_selected", 0) for r in margin_rounds))
        # The tie ledger the PR-18 acceptance pins: rounds where the
        # colluder margin sits EXACTLY at the selection cut (0.0 — a
        # selected colluder's runner-up is its identical twin, and
        # equal f32 scores subtract to an exact zero).  A collapse run
        # is tie-locked nearly every round; a rescue run breaks the
        # lock (strictly-signed margins appear and persist).
        out["margin_tie_rounds"] = sum(1 for v in cms if v == 0.0)
    if backdoor:
        out["final_asr"] = round(
            float(exp.attacker.test_asr(exp.state.weights)), 4)
    if winners:
        counts: dict = {}
        for w in winners:
            counts[w] = counts.get(w, 0) + 1
        top1 = max(counts.values())
        out["top1_share"] = round(top1 / len(winners), 4)
        out["distinct_winners"] = len(counts)
        out["malicious_share"] = round(
            sum(1 for w in winners if w < exp.m_mal) / len(winners), 4)
    return out


def bands_for(cell: str) -> dict:
    return {**DEFAULT_BANDS, **CELL_BANDS.get(cell, {})}


def measure(cells, rounds: int = ROUNDS) -> dict:
    out = {}
    for name in cells:
        t0 = time.time()
        vals = measure_cell(name, CELLS[name], rounds)
        out[name] = {m: {"value": v, "band": bands_for(name).get(m, 0.0)}
                     for m, v in vals.items()}
        print(f"  measured {name} ({time.time() - t0:.1f} s): "
              + "  ".join(f"{m}={v}" for m, v in vals.items()))
    return out


def diff(baseline: dict, measured: dict) -> list:
    """'<cell>.<metric>: ...' drift strings (empty = clean).  Bands come
    from the BASELINE (the policy in force when it was generated);
    missing cells/metrics are drifts too — a silently vanished metric
    must not pass the gate."""
    problems = []
    for cell, metrics in baseline.items():
        got_cell = measured.get(cell)
        if got_cell is None:
            problems.append(f"{cell}: cell not measured")
            continue
        for metric, want in metrics.items():
            got = got_cell.get(metric)
            if got is None:
                problems.append(f"{cell}.{metric}: metric missing from "
                                f"the measurement")
                continue
            w = want["value"]
            g = got["value"] if isinstance(got, dict) else got
            band = float(want.get("band", 0.0))
            if band == 0.0:
                if g != w:
                    problems.append(
                        f"{cell}.{metric}: measured {g} != baseline {w} "
                        f"(exact-match metric: this cell replays "
                        f"bit-deterministically)")
            elif abs(float(g) - float(w)) > band:
                problems.append(
                    f"{cell}.{metric}: measured {g} vs baseline {w} "
                    f"(|delta| {abs(float(g) - float(w)):.4g} > "
                    f"band {band} — beyond any legal ulp-tie flip)")
        for metric in got_cell:
            if metric not in metrics:
                problems.append(f"{cell}.{metric}: new metric not in "
                                f"baseline (regenerate with --update)")
    return problems


def emit_gate_events(path: str, cells: dict, problems: list,
                     status_all: str):
    """One v4 'gate' event per cell (utils/metrics.py schema) — the
    gate's verdict in the same stream every other rollup lives in."""
    from attacking_federate_learning_tpu.utils.metrics import (
        SCHEMA_VERSION, validate_event
    )

    bad_cells = {p.split(".", 1)[0].split(":", 1)[0] for p in problems}
    with open(path, "a") as f:
        for cell, metrics in cells.items():
            rec = {"kind": "gate", "cell": cell,
                   "status": "fail" if cell in bad_cells else status_all,
                   "v": SCHEMA_VERSION, "t": round(time.time(), 3)}
            for m, v in metrics.items():
                rec[m] = v["value"] if isinstance(v, dict) else v
            validate_event(rec)
            f.write(json.dumps(rec) + "\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Deterministic behavioral-drift gate over pinned "
                    "SYNTH_MNIST_HARD defense x attack cells.")
    p.add_argument("--baseline", default=BASELINE)
    p.add_argument("--update", action="store_true",
                   help="write a fresh baseline instead of gating")
    p.add_argument("--cells", default=",".join(CELLS),
                   help="comma-separated subset of the pinned cells")
    p.add_argument("--rounds", type=int, default=ROUNDS,
                   help="rounds per cell (changing this invalidates "
                        "the baseline; it is recorded there)")
    p.add_argument("--strict-env", action="store_true",
                   help="treat a baseline/environment mismatch as a "
                        "failure instead of a skip")
    p.add_argument("--events", default=None, metavar="JSONL",
                   help="append one v4 'gate' event per cell to this "
                        "run log")
    args = p.parse_args(argv)

    cells = [c.strip() for c in args.cells.split(",") if c.strip()]
    unknown = [c for c in cells if c not in CELLS]
    if unknown:
        print(f"unknown cells: {unknown} (known: {sorted(CELLS)})")
        return 2

    env = environment()
    if env["platform"] != "cpu":
        # The pinned cells are CPU replays by construction — never race
        # a TPU relay window for a CI gate (CLAUDE.md).
        print(f"SKIP science_gate: backend is {env['platform']!r}, the "
              f"pinned cells are CPU replays (set JAX_PLATFORMS=cpu)")
        return 0 if not args.strict_env else 1

    if args.update:
        measured = measure(cells, args.rounds)
        payload = {"env": env, "rounds": args.rounds,
                   "generated": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "argv": list(argv or sys.argv[1:]),
                   "policy": "band 0 = exact (bit-deterministic "
                             "replay); band > 0 = measured ulp-tie "
                             "envelope (see module docstring)",
                   "cells": measured}
        with open(args.baseline, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.baseline} ({len(measured)} cells, "
              f"jax {env['jax']}, {env['platform']}, "
              f"{args.rounds} rounds)")
        return 0

    if not os.path.exists(args.baseline):
        print(f"no baseline at {args.baseline}; run with --update first")
        return 2
    with open(args.baseline) as f:
        base = json.load(f)
    benv = base.get("env", {})
    if benv != env or base.get("rounds") != args.rounds:
        msg = (f"environment mismatch: baseline "
               f"(env {benv}, rounds {base.get('rounds')}) vs current "
               f"(env {env}, rounds {args.rounds}) — behavioral "
               f"trajectories are only comparable within one (jax, "
               f"platform, rounds) tuple; regenerate with --update")
        if args.strict_env:
            print(f"FAIL science_gate: {msg}")
            return 1
        print(f"SKIP science_gate: {msg}")
        return 0

    baseline_cells = {c: v for c, v in base["cells"].items() if c in cells}
    measured = measure(cells, args.rounds)
    problems = diff(baseline_cells, measured)
    if args.events:
        emit_gate_events(args.events, measured, problems,
                         "fail" if problems else "pass")
    if problems:
        print(f"FAIL science_gate: {len(problems)} behavioral drift(s)")
        for prob in problems:
            print(f"  {prob}")
        return 1
    n = sum(len(v) for v in measured.values())
    print(f"ok   science_gate: {len(cells)} cells, {n} metrics match "
          f"BEHAVIOR_BASELINE.json (exact where bit-deterministic, "
          f"measured ulp-tie bands elsewhere)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
