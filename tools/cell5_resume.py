"""Resume the full-scale cell-5 grid from the TrimmedMean cells.

The first full-scale run banked the two Krum cells
(logs/grid_summary_r5_krum.jsonl) and then hit the measured XLA:CPU
stable-argsort wall in TrimmedMean/alie (>14 min/round at n=10,000 —
the same ~943 s/call regime BASELINE.md documents).  Per the round-5
CPU-backend policy, the benchmark driver now opts into the native host
kernels at this scale (benchmarks.py cell-5 overrides:
trimmed_mean_impl='host', bulyan_trim_impl='host'); this script runs
the remaining {TrimmedMean, Bulyan} x {alie, backdoor} cells under
exactly those overrides, appending to a separate summary so the banked
Krum rows are never clobbered.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu nice -n 19 \
       python tools/cell5_resume.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    from attacking_federate_learning_tpu.utils.backend import (
        enable_compile_cache, ensure_live_backend
    )

    ensure_live_backend()
    enable_compile_cache()

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.grid import run_grid

    # Mirrors benchmarks.py _cells()[4] + the CPU-backend host opt-ins.
    cfg = ExperimentConfig(
        epochs=10, log_dir="logs", synth_train=4096, synth_test=512,
        dataset=C.MNIST, users_count=10_000, mal_prop=0.24,
        partition="dirichlet", batch_size=32,
        data_placement="host_stream",
        bulyan_selection_impl="host",
        trimmed_mean_impl="host", bulyan_trim_impl="host")
    t0 = time.time()
    # Unique summary path per invocation: run_grid opens its out_path
    # in 'w' mode, so a re-run after a mid-grid failure must not
    # truncate the rows a previous invocation already banked.
    out_path = time.strftime("logs/grid_summary_r5b_%H%M%S.jsonl")
    cells = run_grid(cfg, defenses=["TrimmedMean", "Bulyan"],
                     attacks=["alie", "backdoor"],
                     out_path=out_path)
    print(json.dumps({
        "cell": "noniid_10k_grid_resume", "clients": cfg.users_count,
        "wall_s": round(time.time() - t0, 2), "grid_cells": len(cells),
        "final_accuracies": {f"{c['defense']}/{c['attack']}":
                             c.get("final_accuracy") for c in cells}}))


if __name__ == "__main__":
    main()
