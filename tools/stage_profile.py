#!/usr/bin/env python
"""Stage-ledger capture: profiled flat + hierarchical rounds with the
ISSUE-15 stage scopes live.

The stage taxonomy (utils/costs.py:STAGES — deliver → quarantine →
protect → tier1_aggregate → tier2_aggregate → apply) is threaded
through the engines as ``jax.named_scope`` annotations.  This tool is
the capture leg for that instrument (tools/tpu_capture.sh step 2.7):

- static: per-stage FLOP/byte attribution of the compiled flat and
  hierarchical round programs (utils/costs.py:stage_attribution) plus
  the per-seam wire ledger — the numbers the perf gate's --stageproof
  pins on CPU, re-derived on the live backend;
- profiled: one short span of real rounds per topology under
  ``jax.profiler.trace`` — because the scopes are named_scope
  annotations, the device profile's op breakdown carries the same
  stage tokens, so the trace in ``--trace-dir`` is attributable to the
  taxonomy by name.

``--rehearse`` pins the CPU backend first (no relay needed): same
steps, same JSON lines, profiler trace included — the CPU dress
rehearsal tpu_capture.sh --rehearse runs.  Without it the live device
set is used (never launch bare during a capturable window — the
capture script owns the lock).

Prints one JSON line per cell (flat, hier) on stdout; diagnostics on
stderr.  A cell failure banks an ``error`` record instead of killing
the remaining cells — the relay may flap mid-step.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _force_rehearse_env() -> None:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    from attacking_federate_learning_tpu.cli import apply_backend

    apply_backend("cpu")


CELLS = {
    "flat": dict(defense="Krum"),
    "hier": dict(defense="Krum", aggregation="hierarchical",
                 users_count=64, mal_prop=0.25, megabatch=8,
                 tier2_defense="Krum"),
}


def run_cell(tag: str, overrides: dict, rounds: int,
             trace_root: str | None) -> dict:
    import jax
    import jax.numpy as jnp

    from attacking_federate_learning_tpu import config as C
    from attacking_federate_learning_tpu.attacks import DriftAttack
    from attacking_federate_learning_tpu.config import ExperimentConfig
    from attacking_federate_learning_tpu.core.engine import (
        FederatedExperiment
    )
    from attacking_federate_learning_tpu.data.datasets import load_dataset
    from attacking_federate_learning_tpu.utils.costs import (
        compiled_cost_facts, stage_attribution, stage_scopes_enabled
    )

    base = dict(
        dataset=C.SYNTH_MNIST, users_count=16, mal_prop=0.25,
        batch_size=16, epochs=max(rounds, 2), test_step=max(rounds, 2),
        seed=0, synth_train=512, synth_test=64)
    base.update(overrides)
    cfg = ExperimentConfig(**base)
    ds = load_dataset(cfg.dataset, seed=0, synth_train=base["synth_train"],
                      synth_test=64)
    exp = FederatedExperiment(cfg, attacker=DriftAttack(1.5), dataset=ds)
    rec = {"tool": "stage_profile", "cell": tag,
           "platform": jax.devices()[0].platform,
           "n_devices": len(jax.devices()),
           "stage_scopes_enabled": stage_scopes_enabled(),
           "defense": cfg.defense, "aggregation": cfg.aggregation,
           "cohort": exp.m, "d": exp.flat.dim}

    t0 = time.perf_counter()
    compiled = exp._fused_round.lower(
        exp.state, jnp.asarray(0, jnp.int32)).compile()
    rec["compile_s"] = round(time.perf_counter() - t0, 2)
    facts = compiled_cost_facts(compiled)
    att = stage_attribution(compiled.as_text(), facts)
    rec["coverage"] = {k: round(v, 4) for k, v in att["coverage"].items()}
    rec["stage_flops"] = {s: v["flops"] for s, v in att["stages"].items()}
    rec["stage_bytes"] = {s: v["bytes_accessed"]
                          for s, v in att["stages"].items()}
    rec["unattributed_flops"] = att["unattributed"]["flops"]
    rec["wire"] = exp.wire_ledger()
    if cfg.aggregation == "hierarchical":
        # The PR-12 identity the --stageproof gate pins statically,
        # restated on the live backend's compiled program.
        S = exp._placement.num_shards
        rec["tier1_to_tier2_S_d_4"] = S * exp.flat.dim * 4

    trace_dir = None
    if trace_root:
        trace_dir = os.path.join(trace_root, tag)
        os.makedirs(trace_dir, exist_ok=True)
    ctx = (jax.profiler.trace(trace_dir) if trace_dir
           else contextlib.nullcontext())
    t0 = time.perf_counter()
    with ctx:
        for t in range(rounds):
            exp.run_round(t)
        jax.block_until_ready(exp.state.weights)
    rec["rounds"] = rounds
    rec["wall_s"] = round(time.perf_counter() - t0, 3)
    rec["trace_dir"] = trace_dir

    if trace_dir:
        # ISSUE 16 cross-check: book the capture just taken against
        # the SAME compiled program the static attribution above
        # priced (utils/walls.py — instruction-name join).  The two
        # ledgers must tell one story: booked partition exact, booked
        # op time inside the host wall, and every stage the static
        # side attributes flops to either appears in the booking or is
        # explicitly absent (a capture missing op events — flag unset
        # or TPU-gated no-op — reports walls_verdict='no-op-events'
        # loudly instead of a vacuous pass).
        from attacking_federate_learning_tpu.utils import walls
        wrec = walls.book_trace(trace_dir, compiled.as_text(),
                                name=tag,
                                platform=rec["platform"],
                                rounds=rounds)
        if wrec is None:
            rec["walls_verdict"] = "no-trace-file"
        elif wrec.coverage.get("op_events", 0) == 0:
            rec["walls_verdict"] = "no-op-events"
        else:
            wrec.check()                         # exact partition
            rec["walls"] = {
                "stages": {s: round(v, 3)
                           for s, v in wrec.stages.items()},
                "unattributed_us": round(wrec.unattributed_us, 3),
                "op_time_fraction":
                    wrec.coverage.get("op_time_fraction"),
            }
            booked_s = wrec.total_us / 1e6
            problems = []
            if booked_s > rec["wall_s"] * 1.05:
                problems.append(
                    f"booked {booked_s:.3f}s exceeds host wall "
                    f"{rec['wall_s']:.3f}s")
            for s, fl in rec["stage_flops"].items():
                if fl > 0 and s not in wrec.stages:
                    problems.append(f"stage {s} carries modeled flops "
                                    f"but booked no wall time")
            rec["walls_verdict"] = ("ok" if not problems
                                    else "; ".join(problems))
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Profiled flat + hier rounds with stage scopes "
                    "live; per-stage static attribution + wire ledger")
    ap.add_argument("--rehearse", action="store_true",
                    help="CPU backend (no relay needed)")
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--trace-dir", default="logs/stage_profile_trace",
                    help="jax.profiler trace root ('' disables)")
    args = ap.parse_args(argv)

    if args.rehearse:
        _force_rehearse_env()

    # Op-level trace events need the xprof flag before this process's
    # FIRST compile (XLA parses XLA_FLAGS once); without it the
    # booking cross-check reports walls_verdict='no-op-events'.
    from attacking_federate_learning_tpu.utils.profiling import (
        ensure_op_profiling
    )
    ensure_op_profiling()

    failed = False
    t_start = time.perf_counter()
    for tag, overrides in CELLS.items():
        t_cell = time.perf_counter()
        try:
            rec = run_cell(tag, overrides, args.rounds,
                           args.trace_dir or None)
        except Exception as e:       # noqa: BLE001 — bank the error,
            # keep the remaining cells (the relay may flap mid-step)
            rec = {"tool": "stage_profile", "cell": tag, "error":
                   f"{type(e).__name__}: {e}"}
            failed = True
        print(json.dumps(rec), flush=True)
        # Same [budget] convention as tpu_capture.sh, from inside the
        # tool — a stalled cell is visible in the step log even when
        # the outer timeout kills us before the shell's budget line.
        print(f"[budget] stage_profile.{tag}: "
              f"{time.perf_counter() - t_cell:.1f}s (cum "
              f"{time.perf_counter() - t_start:.1f}s)",
              file=sys.stderr, flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
