#!/usr/bin/env python
"""Pallas defense-kernel micro-bench: the capture-window payload.

Compiles each ops/pallas_defense.py kernel on the CURRENT backend — a
real Mosaic compile when a TPU is live (the first hard evidence the
kernels lower through Mosaic at all), interpret mode otherwise — and
times a few executions with the bench.py fetch-bounded methodology.
One JSON line per kernel on stdout; chatter on stderr, so
tools/tpu_capture.sh can tee the artifact cleanly.

    python tools/pallas_microbench.py [--n N] [--d D] [--rehearse]

--rehearse: the CPU dress-rehearsal stub (tools/tpu_capture.sh
--rehearse): tiny shapes, interpret forced on, same steps and the same
JSON schema — proves the step mechanics without burning a window.

On TPU the fused Krum-score kernel runs the balanced large-tile
configuration (bm=bn=512, bk=1024: tile HBM traffic ~n²·d·8/512 bytes,
matching the MXU's f32 roofline at the 10k point) and the parity check
diffs each kernel against its XLA reference at f32 tolerance — a
Mosaic numeric fault fails loudly here, inside the window, instead of
poisoning a later science run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=2048)
    p.add_argument("--d", type=int, default=79_510)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--rehearse", action="store_true",
                   help="CPU stub: tiny shapes, interpret forced on")
    args = p.parse_args(argv)

    from attacking_federate_learning_tpu.utils.backend import (
        enable_compile_cache, ensure_live_backend,
        install_aot_warning_collapse
    )

    install_aot_warning_collapse()
    if args.rehearse:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    else:
        ensure_live_backend()
    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from attacking_federate_learning_tpu.defenses.kernels import (
        _krum_scores, bulyan, trimmed_mean_of
    )
    from attacking_federate_learning_tpu.ops.distances import (
        pairwise_distances
    )
    from attacking_federate_learning_tpu.ops.pallas_defense import (
        krum_scores_cost, pallas_krum_scores, pallas_median_of,
        pallas_trimmed_mean_of
    )

    dev = jax.devices()[0]
    on_accel = dev.platform not in ("cpu",)
    interpret = None if on_accel else True
    if args.rehearse:
        n, d = 64, 1024
        interpret = True
    else:
        n, d = args.n, args.d
    f = int(0.24 * n)
    log(f"pallas_microbench: backend={dev.platform} n={n} d={d} f={f} "
        f"interpret={interpret}")
    G = jax.jit(lambda k: jax.random.normal(k, (n, d), jnp.float32))(
        jax.random.PRNGKey(0))
    np.asarray(G[:1, :1])    # materialize

    # Large tiles on real hardware (roofline-balanced at 10k); the CI
    # defaults elsewhere keep small-n interpret coverage cheap.
    tiles = (dict(bm=512, bn=512, bk=1024) if on_accel
             else dict(bm=128, bn=128, bk=512))

    def fetch1(out):
        """1-element corner fetch — the only sync that provably waits
        through the relay (bench.py methodology); never a full copy."""
        leaf = jax.tree_util.tree_leaves(out)[0]
        return np.asarray(leaf[(slice(0, 1),) * leaf.ndim])

    def timed(fn):
        out = fn()
        fetch1(out)                                  # compile + warm
        walls = []
        for _ in range(max(1, args.repeats)):
            t0 = time.perf_counter()
            out = fn()
            fetch1(out)
            walls.append(1e3 * (time.perf_counter() - t0))
        return float(np.median(walls)), out

    k_keep = n - f - 1
    cells = [
        ("krum_score_fusion",
         jax.jit(lambda g: pallas_krum_scores(
             g, n, f, interpret=interpret, **tiles)[0]),
         jax.jit(lambda g: _krum_scores(pairwise_distances(g), n, f,
                                        method="sort")),
         krum_scores_cost(n, d, f, **tiles)),
        ("trimmed_mean_tile",
         jax.jit(lambda g: pallas_trimmed_mean_of(
             g, k_keep, interpret=interpret)),
         jax.jit(lambda g: trimmed_mean_of(g, k_keep)), None),
        ("median_tile",
         jax.jit(lambda g: pallas_median_of(g, interpret=interpret)),
         jax.jit(lambda g: jnp.median(g, axis=0)), None),
    ]
    if n <= 2048 or args.rehearse:
        # The exact on-device Bulyan route (selection loop is O(n) trips
        # of O(n²)); bounded to sizes where one execution fits a step.
        cells.append((
            "bulyan_pallas_route",
            jax.jit(lambda g: bulyan(g, n, f, selection_impl="pallas",
                                     trim_impl="pallas"),
                    static_argnums=()),
            jax.jit(lambda g: bulyan(g, n, f)), None))

    rc = 0
    for tag, pal_fn, ref_fn, declared in cells:
        row = {"kernel": tag, "n": n, "d": d, "f": f,
               "backend": dev.platform, "mosaic": bool(on_accel),
               "tiles": tiles if tag == "krum_score_fusion" else None}
        try:
            t0 = time.perf_counter()
            lowered = pal_fn.lower(G)
            compiled = lowered.compile()
            row["compile_s"] = round(time.perf_counter() - t0, 2)
            try:
                from attacking_federate_learning_tpu.utils.costs import (
                    compiled_cost_facts
                )
                row["cost"] = {k: v for k, v in
                               compiled_cost_facts(compiled).items()
                               if k in ("flops", "bytes_accessed",
                                        "temp_bytes")}
            except Exception:
                pass
            if declared:
                row["declared"] = declared
            wall, out = timed(lambda: pal_fn(G))
            row["wall_ms"] = round(wall, 2)
            ref_wall, ref_out = timed(lambda: ref_fn(G))
            row["xla_wall_ms"] = round(ref_wall, 2)
            got, want = np.asarray(out), np.asarray(ref_out)
            denom = np.maximum(np.abs(want), 1e-6)
            row["max_rel_err"] = float(np.max(np.abs(got - want) / denom))
            row["parity_ok"] = bool(row["max_rel_err"] < 5e-3)
            if not row["parity_ok"]:
                rc = 1
        except Exception as e:      # noqa: BLE001 — a Mosaic lowering
            # failure is exactly the evidence this step exists to bank
            row["error"] = f"{type(e).__name__}: {e}"
            rc = 1
        log(f"  {tag}: " + (f"{row.get('wall_ms')} ms (xla "
                            f"{row.get('xla_wall_ms')} ms), rel "
                            f"{row.get('max_rel_err'):.2e}"
                            if "wall_ms" in row
                            else row.get("error", "?")))
        print(json.dumps(row), flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
